"""Order-preserving fixed-width key digests (host encode + device compare).

TPU kernels need fixed-width lanes; FDB keys are variable-length bytes (the
reference's SkipList compares raw memory, SkipList.cpp:302 less()).  We embed
keys into 32-byte digests = 8 big-endian uint32 lanes:

    digest(k) = k[:31] zero-padded to 31 bytes || min(len(k), 32)

The leading SALT_LANES (2 lanes = bytes 0..7) are the TENANT-SALT COLUMN:
multi-tenant traffic prefixes every key with its tenant's fixed 8-byte id
(tenant/map.py), so those bytes land whole in their own lanes and the
remaining 23 prefix bytes cover the tenant-RELATIVE key.  A tenant-relative
key of up to 23 bytes therefore digests exactly — tenant traffic stays on
the TPU fast path instead of flooding the supervisor's long-key recheck
(conflict/supervisor.py).  For non-tenant keys the salt lanes simply hold
the first 8 key bytes; the encoding is one uniform order-embedding either
way.

For keys <= 31 bytes this is a strict order-embedding (the trailing length
marker disambiguates prefixes: "a" < "a\\x00" holds because padding ties are
broken by length).  Keys >= 32 bytes are truncated and share the marker 32;
such collisions are handled conservatively: range begins round DOWN
(enc_down) and range ends round UP (enc_up = enc+1ulp when truncated), so a
digest-space range always covers the true key range.  Conservative widening
can only create extra conflicts (aborts), never missed ones -- see
tests/test_conflict_tpu.py::test_long_keys_conservative.

Digest arrays are PLANAR (structure-of-arrays): uint32[KEY_LANES, N], lane
major.  Lexicographic compares and binary searches then touch one 1-D lane
array at a time — the layout XLA vectorizes well on both CPU and TPU (row
gathers of 8-element rows inside the search loop were measured ~1000x slower
on CPU than planar 1-D gathers), and the natural layout for Pallas kernels.

Device-side helpers give lexicographic comparison over the 8 uint32 lanes and
a vectorized lower/upper-bound binary search against the sorted boundary
array.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

SALT_LANES = 2     # tenant-salt column: bytes 0..7 (the 8-byte tenant prefix)
SALT_BYTES = 4 * SALT_LANES
KEY_LANES = 8
PREFIX_BYTES = 31  # bytes 0..30 of the key; byte 31 is the length marker
DIGEST_BYTES = 4 * KEY_LANES

# Digest of b"" is all zeros; all-0xFF is strictly above every real digest
# (real marker byte <= 32), so it serves as the +inf padding sentinel.
MAX_DIGEST = np.full((KEY_LANES,), 0xFFFFFFFF, dtype=np.uint32)
MIN_DIGEST = np.zeros((KEY_LANES,), dtype=np.uint32)


def max_digest_block(n: int) -> np.ndarray:
    """Planar all-MAX padding block: uint32[KEY_LANES, n]."""
    return np.broadcast_to(MAX_DIGEST[:, None], (KEY_LANES, n)).copy()


def is_truncated(key: bytes) -> bool:
    return len(key) > PREFIX_BYTES


def encode_keys(keys: Sequence[bytes], round_up: bool = False) -> np.ndarray:
    """Encode keys -> planar uint32[6, N]. round_up=True applies the +1ulp
    rounding to truncated keys (for range *ends*).

    Vectorized by grouping keys of equal length: one frombuffer + one fancy
    assignment per distinct length (batches are dominated by one or two key
    widths, so this is ~two numpy ops per batch instead of a per-key loop)."""
    n = len(keys)
    buf = np.zeros((n, DIGEST_BYTES), dtype=np.uint8)
    bump = np.zeros((n,), dtype=bool)
    groups: dict = {}
    for i, k in enumerate(keys):
        groups.setdefault(len(k), []).append(i)
    for length, idxs in groups.items():
        m = min(length, PREFIX_BYTES)
        ii = np.asarray(idxs, dtype=np.intp)
        if m:
            if length <= PREFIX_BYTES:
                data = b"".join(keys[i] for i in idxs)
            else:
                data = b"".join(keys[i][:m] for i in idxs)
            buf[ii, :m] = np.frombuffer(data, dtype=np.uint8).reshape(-1, m)
        buf[ii, PREFIX_BYTES] = min(length, PREFIX_BYTES + 1)
        if round_up and length > PREFIX_BYTES:
            bump[ii] = True
    out = buf.view(np.dtype(">u4")).astype(np.uint32)
    if round_up and bump.any():
        out[bump] = _add_one_ulp(out[bump])
    return np.ascontiguousarray(out.T)


def encode_fixed(mat: np.ndarray, lens: np.ndarray = None,
                 round_up: bool = False) -> np.ndarray:
    """Vectorized digest encode from a byte matrix: uint8[N, L] -> uint32[6, N].

    `mat` holds keys as rows of a fixed-width byte matrix (zero-padded on the
    right); `lens` gives per-key true lengths (default: all L).  This is the
    zero-Python-loop path for bulk callers (the proxy/resolver pipeline and
    bench.py); semantics identical to encode_keys."""
    n, width = mat.shape
    buf = np.zeros((n, DIGEST_BYTES), dtype=np.uint8)
    m = min(width, PREFIX_BYTES)
    if lens is None:
        if m:
            buf[:, :m] = mat[:, :m]
        buf[:, PREFIX_BYTES] = min(width, PREFIX_BYTES + 1)
        out = buf.view(np.dtype(">u4")).astype(np.uint32)
        if round_up and width > PREFIX_BYTES:
            out = _add_one_ulp(out)
        return np.ascontiguousarray(out.T)
    lens = np.asarray(lens, dtype=np.int64)
    if m:
        valid = np.arange(m)[None, :] < lens[:, None]
        buf[:, :m] = np.where(valid, mat[:, :m], 0)
    buf[:, PREFIX_BYTES] = np.minimum(lens, PREFIX_BYTES + 1)
    out = buf.view(np.dtype(">u4")).astype(np.uint32)
    if round_up:
        bump = lens > PREFIX_BYTES
        if bump.any():
            out[bump] = _add_one_ulp(out[bump])
    return np.ascontiguousarray(out.T)


def _add_one_ulp(d: np.ndarray) -> np.ndarray:
    """Add 1 to the 32-byte big-endian integer formed by the lanes.

    d: uint32[N, 6] (row-major, pre-transpose)."""
    d = d.copy()
    carry = np.ones(d.shape[0], dtype=bool)
    for lane in range(KEY_LANES - 1, -1, -1):
        d[carry, lane] = d[carry, lane] + np.uint32(1)
        carry = carry & (d[:, lane] == 0)
    return d


def planar_to_s24(planar: np.ndarray) -> np.ndarray:
    """Host: planar uint32[8, N] -> numpy S<DIGEST_BYTES>[N] whose ordering
    equals digest lexicographic order (the big-endian byte concatenation).
    (Name kept from the 24-byte era; the width tracks DIGEST_BYTES.)

    Feeds np.sort / np.unique / np.searchsorted so batch key-grouping can
    run on the HOST — the basis of the sort-free device point path
    (conflict/fused.py): a multi-operand device lax.sort costs minutes of
    XLA compile time per shape over the TPU tunnel and dominated the
    per-batch step.  numpy's S-dtype trailing-NUL padding conflates only
    digests differing solely in trailing zero bytes; every non-empty key's
    digest ends with a nonzero length marker and the empty key's digest is
    all zeros, so no two DISTINCT digests are conflated."""
    n = planar.shape[1]
    rows = (np.ascontiguousarray(planar.T).astype(">u4")
            .view(np.uint8).reshape(n, DIGEST_BYTES))
    return np.ascontiguousarray(rows).view("S%d" % DIGEST_BYTES).ravel()


# ---------------------------------------------------------------------------
# Device-side lexicographic comparison and binary search (planar layout)
# ---------------------------------------------------------------------------

def lex_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a < b lexicographically. a, b: uint32[6, ...] (planar) -> bool[...]."""
    lt = a[KEY_LANES - 1] < b[KEY_LANES - 1]
    for lane in range(KEY_LANES - 2, -1, -1):
        lt = jnp.where(a[lane] == b[lane], lt, a[lane] < b[lane])
    return lt


def lex_less_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return ~lex_less(b, a)


def lex_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=0)


def lex_max_cols(a: jnp.ndarray, b_col: jnp.ndarray) -> jnp.ndarray:
    """Columnwise lexicographic max(a[:, i], b_col); a: [6, N], b_col: [6].
    Used to clip digest ranges to a key-range shard's bounds."""
    b = jnp.broadcast_to(b_col[:, None], a.shape)
    return jnp.where(lex_less(a, b)[None, :], b, a)


def lex_min_cols(a: jnp.ndarray, b_col: jnp.ndarray) -> jnp.ndarray:
    b = jnp.broadcast_to(b_col[:, None], a.shape)
    return jnp.where(lex_less(b, a)[None, :], b, a)


ROW_PAD = 8  # gather row width: the 8 key lanes exactly fill a row


def planar_to_rows(planar: jnp.ndarray) -> jnp.ndarray:
    """uint32[8, N] -> uint32[N, 8] interleaved rows (pad lanes zero).

    TPU gathers/scatters of whole rows run ~40x faster than six strided
    per-lane accesses; use rows for any digest gather/scatter with dynamic
    indices and convert back with rows_to_planar.  XLA CSEs repeated
    conversions of the same array inside one jit."""
    if ROW_PAD == KEY_LANES:
        return planar.T
    n = planar.shape[1]
    return jnp.concatenate(
        [planar.T, jnp.zeros((n, ROW_PAD - KEY_LANES), dtype=planar.dtype)],
        axis=1)


def rows_to_planar(rows: jnp.ndarray) -> jnp.ndarray:
    """uint32[N, 8] -> uint32[6, N]."""
    return rows[:, :KEY_LANES].T


def gather_cols(planar: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """planar[:, idx] via one row gather: uint32[6, N], int32[Q] -> [6, Q]."""
    return rows_to_planar(planar_to_rows(planar)[idx])


def rank_count(positions: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """counts[i] = #{j : positions[j] <= i} for i in [0, out_len).

    The dual of a binary search with MANY queries into a SMALL array —
    note the tie side FLIPS across the duality:

        searchsorted_right(small, big) == rank_count(
            searchsorted_left(big, small), len(big))
        searchsorted_left(small, big)  == rank_count(
            searchsorted_right(big, small), len(big))

    (#{j: small_j <= big_i} counts j with left-pos <= i; #{j: small_j <
    big_i} counts j with right-pos <= i.)  Costs one histogram scatter-add
    + one cumsum instead of log2(len(small)) gathers per big element.
    Entries with positions[j] >= out_len are never counted (padding
    convention: pad queries resolve to the pad region).  Property-tested
    in tests/test_conflict_tpu.py::test_rank_count_duality."""
    hist = jnp.zeros((out_len + 1,), jnp.int32).at[
        jnp.clip(positions, 0, out_len)].add(1)
    return jnp.cumsum(hist[:out_len])


def _searchsorted(sorted_keys: jnp.ndarray, queries: jnp.ndarray,
                  side_left) -> jnp.ndarray:
    """Vectorized branchless binary search.

    sorted_keys: uint32[6, CAP]; queries: uint32[6, Q].  Returns, per query
    q: first index i with keys[i] >= q (left) or keys[i] > q (right).  CAP
    must be a power of two (capacity arrays are padded with MAX_DIGEST above
    the live size).

    side_left is either a Python bool (one tie side for the whole query
    block) or a bool[Q] array giving the tie side PER QUERY — the fused
    probe pass (searchsorted_interval) packs begin probes (right side)
    and end probes (left side) into one loop over the same table, halving
    the sequential probe loops per history check.

    The probe-gather layout is BACKEND-ADAPTIVE (chosen at trace time):

    - TPU: interleaved ROWS (uint32[CAP, 8]: 6 lanes + pad) — ONE row
      gather per probe.  Measured on v5e: ~40x faster than per-lane
      gathers (which ran at ~74M elem/s).  The planar->rows transpose is
      CSE'd by XLA when several searches share one jit.
    - CPU: per-lane planar 1-D gathers — row gathers measured ~1000x
      SLOWER there (XLA:CPU scalarizes the 8-wide row loads), and the
      XLA-CPU path serves the bench fallback and the whole test suite."""
    import jax as _jax
    cap = sorted_keys.shape[1]
    nbits = int(cap).bit_length() - 1
    assert cap == 1 << nbits, f"capacity {cap} not a power of two"
    use_rows = _jax.default_backend() != "cpu"
    if use_rows:
        rows = planar_to_rows(sorted_keys)
    nq = queries.shape[1]
    per_query_side = not isinstance(side_left, bool)
    lo = jnp.zeros((nq,), dtype=jnp.int32)
    # Binary search maintaining: result in (lo, hi]; start hi = cap.
    hi = jnp.full((nq,), cap, dtype=jnp.int32)
    q_lanes = [queries[lane] for lane in range(KEY_LANES)]
    for _ in range(nbits + 1):
        active = lo < hi
        mid = (lo + hi) >> 1
        midc = jnp.minimum(mid, cap - 1)
        if use_rows:
            mk = rows[midc]                 # [nq, 8] single row gather
            mk_lanes = [mk[:, lane] for lane in range(KEY_LANES)]
        else:
            mk_lanes = [sorted_keys[lane][midc] for lane in range(KEY_LANES)]
        # lexicographic keys[midc] < q (or <=) via per-lane where-chain
        last = KEY_LANES - 1
        if per_query_side:
            # Mixed sides: lt and eq chains share the same lane gathers;
            # descend-right iff keys[mid] < q (left side) / <= q (right).
            lt = mk_lanes[last] < q_lanes[last]
            eq = mk_lanes[last] == q_lanes[last]
            for lane in range(KEY_LANES - 2, -1, -1):
                same = mk_lanes[lane] == q_lanes[lane]
                lt = jnp.where(same, lt, mk_lanes[lane] < q_lanes[lane])
                eq = eq & same
            cmp = jnp.where(side_left, lt, lt | eq)
        else:
            if side_left:
                cmp = mk_lanes[last] < q_lanes[last]    # keys[mid] < q
            else:
                cmp = mk_lanes[last] <= q_lanes[last]   # keys[mid] <= q
            for lane in range(KEY_LANES - 2, -1, -1):
                cmp = jnp.where(mk_lanes[lane] == q_lanes[lane], cmp,
                                mk_lanes[lane] < q_lanes[lane])
        lo = jnp.where(active & cmp, mid + 1, lo)
        hi = jnp.where(active & ~cmp, mid, hi)
    return hi


def searchsorted_left(sorted_keys: jnp.ndarray, queries: jnp.ndarray) -> jnp.ndarray:
    return _searchsorted(sorted_keys, queries, True)


def searchsorted_right(sorted_keys: jnp.ndarray, queries: jnp.ndarray) -> jnp.ndarray:
    return _searchsorted(sorted_keys, queries, False)


def searchsorted_interval(sorted_keys: jnp.ndarray, q_begin: jnp.ndarray,
                          q_end: jnp.ndarray):
    """Fused history probe over ONE table: (searchsorted_right(keys,
    q_begin), searchsorted_left(keys, q_end)) computed by a single
    binary-search loop over the concatenated query block.

    The two-tier history check needs, per range [b, e): the segment
    containing b (right probe - 1) and the first boundary >= e (left
    probe).  Running both probes through one loop halves the number of
    sequential probe loops per table (base and delta: four loops -> two)
    — the same total gather work, scheduled as one pass with twice the
    gather width, which XLA batches better and compiles once."""
    nb = q_begin.shape[1]
    queries = jnp.concatenate([q_begin, q_end], axis=1)
    side = jnp.concatenate([
        jnp.zeros((nb,), bool),
        jnp.ones((q_end.shape[1],), bool)])
    pos = _searchsorted(sorted_keys, queries, side)
    return pos[:nb], pos[nb:]
