"""Batched range-maximum queries via a sparse table (device-side).

The reference's skip list answers "max write version over key range" by
walking node pyramids with per-level max versions (SkipList.cpp:695
CheckMax::advance).  The TPU formulation: segment versions live in a flat
int32[CAP] array; we precompute the doubling sparse table
M[j][i] = max(v[i .. i+2^j)) once per batch (O(CAP log CAP), embarrassingly
parallel) and answer each query [lo, hi) with two gathers:
max(M[j][lo], M[j][hi - 2^j]) where j = floor(log2(hi - lo)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.int32(-(1 << 31) + 1)


def build_sparse_table(values: jnp.ndarray) -> jnp.ndarray:
    """values: int32[CAP] -> M: int32[LOG+1, CAP]; CAP must be a power of 2."""
    cap = values.shape[0]
    log = max((cap - 1).bit_length(), 1)
    rows = [values]
    cur = values
    for j in range(log):
        shift = 1 << j
        shifted = jnp.concatenate(
            [cur[shift:], jnp.full((shift,), NEG_INF, dtype=cur.dtype)])
        cur = jnp.maximum(cur, shifted)
        rows.append(cur)
    return jnp.stack(rows)


def range_max(table: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """Per-query max(values[lo:hi]); empty ranges (hi<=lo) -> NEG_INF.

    lo, hi: int32[N] with 0 <= lo, hi <= CAP."""
    length = hi - lo
    valid = length > 0
    safe_len = jnp.maximum(length, 1)
    # floor(log2(len)) via bit width
    j = 31 - jax.lax.clz(safe_len.astype(jnp.int32))
    left = table[j, lo]
    right = table[j, jnp.maximum(hi - (1 << j), 0)]
    return jnp.where(valid, jnp.maximum(left, right), NEG_INF)
