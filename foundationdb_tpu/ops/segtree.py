"""Static-shape interval min-cover structure (device-side).

Answers, for a universe of U elementary gaps and a set of weighted intervals
(span [l, r) with weight w), the query "min weight over intervals overlapping
gap range [a, b)".  Used by the fused conflict kernel's intra-batch pass: the
weight is the writer's transaction index, so a read range conflicts iff
min-overlapping-writer < its own transaction index (strictly earlier writer).

Construction is an iterative segment tree with all control flow static:
  * span_update: each interval min-updates <= 2 nodes per level (log U levels,
    two masked scatter-mins each);
  * pushdown: one top-down level sweep propagates ancestor minima to leaves,
    producing cover[g] = min weight over intervals covering gap g;
  * range queries over cover[] then use the sparse range-min table.

Everything is O((N + U) log U) with static shapes -- XLA compiles one program
per (N, U) bucket, no data-dependent shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

INF_I32 = jnp.int32((1 << 31) - 1)


def interval_min_cover(l: jnp.ndarray, r: jnp.ndarray, w: jnp.ndarray,
                       valid: jnp.ndarray, log_u: int) -> jnp.ndarray:
    """cover[g] = min{w[i] : valid[i] and l[i] <= g < r[i]} (INF if none).

    l, r: int32[N] spans over [0, U) with U = 1 << log_u; w: int32[N]."""
    u = 1 << log_u
    tree = jnp.full((2 * u,), INF_I32, dtype=jnp.int32)
    wv = jnp.where(valid & (l < r), w, INF_I32)
    li = jnp.clip(l, 0, u) + u
    ri = jnp.clip(r, 0, u) + u
    # Standard iterative decomposition, vectorized across intervals: at each
    # level, an odd left cursor contributes node li (then li+=1), an odd right
    # cursor contributes node ri-1 (then ri-=1); both cursors then halve.
    for _ in range(log_u + 1):
        active = li < ri
        take_l = active & (li & 1 == 1)
        take_r = active & (ri & 1 == 1)
        idx_l = jnp.where(take_l, li, 0)          # node 0 is unused padding
        idx_r = jnp.where(take_r, ri - 1, 0)
        tree = tree.at[idx_l].min(jnp.where(take_l, wv, INF_I32))
        tree = tree.at[idx_r].min(jnp.where(take_r, wv, INF_I32))
        li = (li + (li & 1)) >> 1
        ri = (ri - (ri & 1)) >> 1
    # Pushdown: children inherit parent minima level by level.
    for level in range(1, log_u + 1):
        lo = 1 << level
        parents = tree[lo >> 1: lo]
        seg = tree[lo: 2 * lo]
        seg = jnp.minimum(seg, jnp.repeat(parents, 2))
        tree = tree.at[lo: 2 * lo].set(seg)
    return tree[u: 2 * u]


def build_min_table(values: jnp.ndarray) -> jnp.ndarray:
    """Doubling sparse table for range-MIN.

    min(x) == -max(-x), so reuse the range-max sparse table on negated
    values (the sentinels map onto each other: -INF_I32 == NEG_INF).
    Pair only with range_min below — rows hold negated partial maxima."""
    from .rangemax import build_sparse_table
    return build_sparse_table(-values)


def range_min(table: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """Per-query min(values[lo:hi]) over a build_min_table table; empty
    ranges -> INF.  lo, hi: int32[N] with 0 <= lo, hi <= CAP."""
    from .rangemax import range_max
    return -range_max(table, lo, hi)
