"""foundationdb_tpu: a TPU-native distributed transactional key-value framework.

A brand-new framework with the capabilities of FoundationDB (reference:
/root/reference, v7.1): ordered keys, strict-serializable ACID transactions,
an optimistic-concurrency commit pipeline (GRV proxies -> commit proxies ->
resolvers -> transaction logs -> versioned storage servers), epoch-based
recovery, and deterministic simulation testing.

It is NOT a port.  The compute-heavy heart of the commit pipeline -- the
Resolver's per-batch range-conflict detection (reference:
fdbserver/Resolver.actor.cpp:104, fdbserver/SkipList.cpp) -- is reformulated
TPU-first as a batched interval-overlap kernel in JAX/Pallas over HBM-resident
sorted key-digest arrays, shardable over a `jax.sharding.Mesh` by key range
with OR-reduced (psum) conflict bitmaps.  The host runtime (actors, RPC,
simulation, roles) is a deterministic event-loop runtime in Python with native
C++ components under native/.

Layer map (mirrors reference layering flow -> fdbrpc -> fdbclient -> fdbserver):
  core/      -- futures, deterministic scheduler, RNG, knobs, trace, buggify
  rpc/       -- typed request streams over a simulated (or real) network
  txn/       -- transaction payload types (mutations, conflict ranges, versions)
  conflict/  -- ConflictSet implementations: CPU oracle + TPU backend selector
  ops/       -- JAX/Pallas device kernels (digest compare, search, range-max)
  parallel/  -- mesh sharding of the conflict window, collectives
  server/    -- roles: master, grv proxy, commit proxy, resolver, tlog, storage
  client/    -- Database/Transaction API with RYW semantics and retry loop
  sim/       -- deterministic cluster simulation harness
  workloads/ -- composable test workloads (Cycle, ConflictRange model check, ...)
  models/    -- flagship end-to-end pipeline model used by __graft_entry__
  utils/     -- misc helpers
"""

__version__ = "0.1.0"
